"""MVCC garbage collection worker.

Reference parity: pkg/store/gcworker/gc_worker.go — compute a safe point
(now - gc life time), resolve stale locks below it, then drop unreachable
versions. Single-process build runs it on a daemon thread or on demand
(tests call run_once)."""

from __future__ import annotations

import threading
import time
from typing import Optional

from tidb_tpu.kv.kv import TimestampOracle
from tidb_tpu.kv.memstore import MemStore


class GCWorker:
    def __init__(self, store: MemStore, life_ms: int = 600_000, interval_s: float = 600.0):
        self.store = store
        self.life_ms = life_ms
        self.interval_s = interval_s
        self.safe_point = 0
        self.runs = 0
        self.last_pruned = 0
        # background-loop failure visibility (the loop itself never dies)
        self.sweep_errors = 0
        self.last_error = ""
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def compute_safe_point(self) -> int:
        now_ms = int(time.time() * 1000)
        return max(0, (now_ms - self.life_ms)) << TimestampOracle._PHYSICAL_SHIFT

    def run_once(self, safe_point: Optional[int] = None) -> int:
        """One GC cycle: resolve expired locks under the safe point, then
        prune versions. Returns pruned version count."""
        sp = self.compute_safe_point() if safe_point is None else safe_point
        # service safepoints (log-backup checkpoints) pin GC — versions the
        # change feed has not captured yet must survive (ref: PD service
        # safepoints registered by br log backup)
        svc = self.store.min_service_safepoint()
        if svc is not None:
            sp = min(sp, svc)
        # resolve abandoned locks first (ref: gc_worker resolveLocks phase)
        with self.store._mu:
            stale = [
                (k, lock) for k, lock in self.store._locks.items() if lock.start_ts < sp and lock.expired()
            ]
        for k, lock in stale:
            self.store.resolve_lock(k, lock)
        pruned = self.store.gc(sp)
        self.safe_point = max(self.safe_point, sp)
        self.runs += 1
        self.last_pruned = pruned
        return pruned

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.run_once()
                except Exception as e:
                    # GC must never take the server down, but a failing sweep
                    # must be visible (sys_snapshot ships the registry)
                    self.sweep_errors += 1
                    self.last_error = f"{type(e).__name__}: {e}"

        self._thread = threading.Thread(target=loop, name="gc-worker", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1)
            self._thread = None
