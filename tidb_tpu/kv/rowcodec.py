"""Row value codec — fixed-slot layout with vectorized bulk decode.

Reference parity: pkg/util/rowcodec (compact row format v2, encoder.go). The
reference optimizes for byte compactness; this rebuild optimizes for
*vectorized decode into device-ready columns*:

    row := version(1B) | null_bitmap(ceil(n/8) B) | fixed_slots(8B × n_fixed)
           | varlen_section( for each string col: u32 len + bytes )

All fixed-width columns (int64/float64 physical) sit at schema-constant byte
offsets, so a batch of rows decodes with one numpy gather per column —
``decode_fixed_bulk`` — instead of a per-row loop. String columns decode in a
per-column loop and dictionary-encode at columnar-cache build time.

The column set and order come from the table schema version; rows embed only
the schema version, not column ids (compactness + self-description traded for
decode speed; schema history lives in the catalog).
"""

from __future__ import annotations

import struct
from typing import Sequence

import numpy as np

from tidb_tpu.types import FieldType, TypeKind

ROW_VERSION = 1


class RowSchema:
    """Decode plan for one table schema version: which physical slot each
    column occupies."""

    def __init__(self, ftypes: Sequence[FieldType]):
        self.ftypes = list(ftypes)
        self.n = len(self.ftypes)
        self.bitmap_len = (self.n + 7) // 8
        self.fixed_idx: list[int] = []  # column positions with fixed slots
        self.string_idx: list[int] = []  # column positions in varlen section
        for i, ft in enumerate(self.ftypes):
            if ft.kind in (TypeKind.STRING, TypeKind.JSON):
                self.string_idx.append(i)
            else:
                self.fixed_idx.append(i)
        self.fixed_base = 1 + self.bitmap_len
        self.varlen_base = self.fixed_base + 8 * len(self.fixed_idx)
        # column position → slot number among fixed
        self._fixed_slot = {c: s for s, c in enumerate(self.fixed_idx)}
        self._string_slot = {c: s for s, c in enumerate(self.string_idx)}

    def fixed_offset(self, col: int) -> int:
        return self.fixed_base + 8 * self._fixed_slot[col]

    def string_slot(self, col: int) -> int:
        return self._string_slot[col]


def encode_row(schema: RowSchema, values: Sequence) -> bytes:
    """``values`` are *physical* values (int/float per FieldType.device_dtype)
    or None for NULL; string columns take raw ``bytes``."""
    out = bytearray([ROW_VERSION])
    bitmap = bytearray(schema.bitmap_len)
    for i, v in enumerate(values):
        if v is None:
            bitmap[i >> 3] |= 1 << (i & 7)
    out += bitmap
    for c in schema.fixed_idx:
        v = values[c]
        if v is None:
            out += b"\x00" * 8
        elif schema.ftypes[c].kind == TypeKind.FLOAT:
            out += struct.pack("<d", float(v))
        else:
            out += struct.pack("<q", int(v))
    for c in schema.string_idx:
        v = values[c]
        if v is None:
            out += struct.pack("<I", 0)
        else:
            if isinstance(v, str):
                v = v.encode("utf-8")
            out += struct.pack("<I", len(v))
            out += v
    return bytes(out)


def decode_row(schema: RowSchema, buf: bytes) -> list:
    """Single-row decode (write path read-modify, point gets)."""
    if buf[0] != ROW_VERSION:
        raise ValueError(f"bad row version {buf[0]:#x} (corrupt or foreign encoding)")
    vals: list = [None] * schema.n
    bitmap = buf[1 : 1 + schema.bitmap_len]
    off = schema.fixed_base
    for c in schema.fixed_idx:
        if not (bitmap[c >> 3] >> (c & 7)) & 1:
            if schema.ftypes[c].kind == TypeKind.FLOAT:
                vals[c] = struct.unpack_from("<d", buf, off)[0]
            else:
                vals[c] = struct.unpack_from("<q", buf, off)[0]
        off += 8
    off = schema.varlen_base
    for c in schema.string_idx:
        (ln,) = struct.unpack_from("<I", buf, off)
        off += 4
        if (bitmap[c >> 3] >> (c & 7)) & 1:
            vals[c] = None
        else:
            vals[c] = buf[off : off + ln]
        off += ln
    return vals


def decode_fixed_bulk(
    schema: RowSchema, buf: bytes, starts: np.ndarray, cols: Sequence[int]
) -> tuple[list[np.ndarray], list[np.ndarray]]:
    """Vectorized decode of fixed-width columns for many rows.

    ``buf`` is the concatenation of row values; ``starts[i]`` is the byte
    offset of row i. Returns ([data...], [validity...]) per requested col.
    """
    from tidb_tpu.native.bulk import decode_fixed as native_decode_fixed

    nat = native_decode_fixed(buf, starts, schema, cols) if len(starts) else None
    if nat is not None:
        datas, valids = [], []
        for (data, valid), c in zip(nat, cols):
            if schema.ftypes[c].kind == TypeKind.FLOAT:
                data = data.view("<f8")
            datas.append(data)
            valids.append(valid)
        return datas, valids
    arr = np.frombuffer(buf, dtype=np.uint8)
    n = len(starts)
    datas, valids = [], []
    # null bitmap bytes: gather bitmap region once
    bm = arr[starts[:, None] + (1 + np.arange(schema.bitmap_len))[None, :]] if schema.bitmap_len else None
    for c in cols:
        ft = schema.ftypes[c]
        byte_idx = c >> 3
        bit = c & 7
        null = ((bm[:, byte_idx] >> bit) & 1).astype(bool) if bm is not None else np.zeros(n, bool)
        off = schema.fixed_offset(c)
        raw = arr[starts[:, None] + (off + np.arange(8))[None, :]]
        raw = np.ascontiguousarray(raw)
        if ft.kind == TypeKind.FLOAT:
            data = raw.view("<f8").ravel().astype(np.float64)
        else:
            data = raw.view("<i8").ravel().astype(np.int64)
        data = np.where(null, 0, data)
        datas.append(data)
        valids.append(~null)
    return datas, valids


def decode_strings_bulk(
    schema: RowSchema, buf: bytes, starts: np.ndarray, col: int
) -> tuple[list[bytes | None], np.ndarray]:
    """Per-row loop over the varlen section for one string column."""
    slot = schema.string_slot(col)
    out: list[bytes | None] = []
    validity = np.ones(len(starts), dtype=bool)
    for i in range(len(starts)):
        off = int(starts[i]) + schema.varlen_base
        bitmap_off = int(starts[i]) + 1
        for s in range(slot + 1):
            (ln,) = struct.unpack_from("<I", buf, off)
            off += 4
            if s == slot:
                c = schema.string_idx[s]
                if (buf[bitmap_off + (c >> 3)] >> (c & 7)) & 1:
                    out.append(None)
                    validity[i] = False
                else:
                    out.append(buf[off : off + ln])
                break
            off += ln
    return out, validity
