"""The TTL worker: for every TTL-enabled table, delete rows whose TTL column
fell behind now - interval, in bounded batches through the normal DML path
(so MVCC, indexes, partitions, and stats counters all stay consistent) —
the ttlworker job/scan/delete pipeline collapsed to its SQL essence."""

from __future__ import annotations

import datetime


def run_ttl_once(db, now: datetime.datetime | None = None, batch: int = 10_000) -> dict[str, int]:
    """One sweep over all databases; returns {db.table: rows deleted}."""
    now = now or datetime.datetime.now()
    out: dict[str, int] = {}
    s = db.session()
    for db_name in db.catalog.databases():
        for tname in db.catalog.tables(db_name):
            t = db.catalog.table(db_name, tname)
            if t.ttl_col_offset < 0 or not t.ttl_enable:
                continue
            col = t.columns[t.ttl_col_offset]
            cutoff = now - datetime.timedelta(days=t.ttl_days)
            from tidb_tpu.types import TypeKind

            if col.ftype.kind == TypeKind.DATE:
                lit = cutoff.date().isoformat()
            else:
                lit = cutoff.isoformat(sep=" ", timespec="seconds")
            total = 0
            while True:
                n = s.execute(
                    f"DELETE FROM `{db_name}`.`{tname}` WHERE `{col.name}` < '{lit}' LIMIT {batch}"
                ).affected
                total += n
                if n < batch:
                    break
            if total:
                out[f"{db_name}.{tname}"] = total
    return out
