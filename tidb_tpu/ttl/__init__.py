"""TTL row expiry (ref: pkg/ttl — ttlworker/job_manager.go:98 scanning
expired rows via SQL jobs on the timer framework)."""

from tidb_tpu.ttl.worker import run_ttl_once

__all__ = ["run_ttl_once"]
