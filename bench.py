"""Benchmark: TPC-H Q1/Q6-shaped aggregation pushdown, TPU engine vs the
host (numpy/unistore-analog) reference engine.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where value
is the TPU engine's Q1 scan+agg throughput (rows/sec/chip, end-to-end SQL
path, warm device cache) and vs_baseline is the speedup over the host
engine on identical data and plans (BASELINE.md configs 2 and 3).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_ROWS = int(os.environ.get("BENCH_ROWS", "20000000"))
# join bench tables stay at a fixed size so the host-reference join time
# doesn't swamp the run as N_ROWS scales
N_JOIN = int(os.environ.get("BENCH_JOIN_ROWS", "4000000"))
# best-of sampling: the remote-tunnel RTT jitters ±40ms per TPU call, so the
# tpu side needs several draws for a stable minimum; the host engine runs
# in-process numpy with no tunnel in the path, so one timed draw (plus the
# warm-up) is representative and keeps multi-second reference queries cheap
REPS = int(os.environ.get("BENCH_REPS", "7"))
HOST_REPS = int(os.environ.get("BENCH_HOST_REPS", "1"))

Q1 = """SELECT l_returnflag, l_linestatus,
    SUM(l_quantity), SUM(l_extendedprice),
    SUM(l_extendedprice * (1 - l_discount)),
    SUM(l_extendedprice * (1 - l_discount) * (1 + l_tax)),
    AVG(l_quantity), AVG(l_extendedprice), AVG(l_discount), COUNT(*)
  FROM lineitem WHERE l_shipdate <= DATE '1998-09-02'
  GROUP BY l_returnflag, l_linestatus ORDER BY l_returnflag, l_linestatus"""

Q6 = """SELECT SUM(l_extendedprice * l_discount) FROM lineitem
  WHERE l_shipdate >= DATE '1994-01-01' AND l_shipdate < DATE '1995-01-01'
    AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24"""

# the remaining BASELINE.json configs: full-scan count, Q10-style TopN
# pushdown, Q3-style MPP join (2-way exchange); plus a windowed config
# (ranking + framed agg over sorted partitions — the device window kernel)
WINDOWED = """SELECT l_returnflag, MAX(rn), MAX(cum) FROM (
    SELECT l_returnflag,
           ROW_NUMBER() OVER (PARTITION BY l_returnflag ORDER BY l_extendedprice) AS rn,
           SUM(l_quantity) OVER (PARTITION BY l_returnflag ORDER BY l_extendedprice) AS cum
    FROM lineitem WHERE l_shipdate < DATE '1994-01-01') t
    GROUP BY l_returnflag ORDER BY l_returnflag"""
COUNT_STAR = "SELECT COUNT(*) FROM lineitem"
Q10 = """SELECT l_returnflag, l_extendedprice FROM lineitem
  WHERE l_shipdate >= DATE '1994-01-01'
  ORDER BY l_extendedprice DESC LIMIT 20"""
Q3 = """SELECT o_odate, SUM(l_extendedprice) AS rev FROM lineitem2, orders
  WHERE l_orderkey = o_orderkey GROUP BY o_odate ORDER BY rev DESC, o_odate LIMIT 10"""
Q1_ROLLUP = """SELECT l_returnflag, l_linestatus, COUNT(*), SUM(l_quantity),
    SUM(l_extendedprice) FROM lineitem WHERE l_shipdate <= DATE '1998-09-02'
  GROUP BY l_returnflag, l_linestatus WITH ROLLUP
  ORDER BY GROUPING(l_returnflag), GROUPING(l_linestatus), l_returnflag, l_linestatus"""


def setup():
    import numpy as np

    import tidb_tpu
    from tidb_tpu.executor.load import bulk_load

    db = tidb_tpu.open(region_split_keys=1 << 62)  # single region per chip
    db.execute(
        """CREATE TABLE lineitem (
        l_quantity DECIMAL(12,2), l_extendedprice DECIMAL(12,2),
        l_discount DECIMAL(12,2), l_tax DECIMAL(12,2),
        l_returnflag VARCHAR(1), l_linestatus VARCHAR(1), l_shipdate DATE)"""
    )
    rng = np.random.default_rng(0)
    n = N_ROWS
    cols = [
        rng.integers(100, 5100, n),  # qty  (scaled 2)
        rng.integers(100000, 9000000, n),  # extendedprice
        rng.integers(0, 11, n),  # discount
        rng.integers(0, 9, n),  # tax
        np.array([b"A", b"N", b"R"], dtype="S1")[rng.integers(0, 3, n)],
        np.array([b"F", b"O"], dtype="S1")[rng.integers(0, 2, n)],
        8036 + rng.integers(0, 2525, n),  # 1992-01-01 .. ~1998-12
    ]
    t0 = time.time()
    bulk_load(db, "lineitem", cols)
    load_s = time.time() - t0

    # Q3-style join tables: lineitem2 ⋈ orders on an integer key
    nj = N_JOIN
    n_orders = max(nj // 10, 1)
    db.execute("CREATE TABLE orders (o_orderkey BIGINT PRIMARY KEY, o_odate BIGINT)")
    db.execute(
        "CREATE TABLE lineitem2 (l_orderkey BIGINT, l_extendedprice DECIMAL(12,2))"
    )
    bulk_load(db, "orders", [np.arange(n_orders), 8036 + rng.integers(0, 100, n_orders)])
    bulk_load(
        db,
        "lineitem2",
        [rng.integers(0, n_orders, nj), rng.integers(100000, 9000000, nj)],
    )
    db.execute("ANALYZE TABLE orders")
    db.execute("ANALYZE TABLE lineitem2")
    return db, load_s


def timed(session, sql, reps):
    session.query(sql)  # warm (compile + cache build)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        session.query(sql)
        best = min(best, time.perf_counter() - t0)
    return best


QPS_THREADS = int(os.environ.get("BENCH_QPS_THREADS", "8"))
QPS_ITERS = int(os.environ.get("BENCH_QPS_ITERS", "200"))


def concurrent_qps(db, worker, n_threads, iters, setup=None):
    from tidb_tpu.bench.qps import concurrent_qps as _cq

    return _cq(db, worker, n_threads, iters, setup=setup)


def qps_point_select(db) -> float:
    """Point-select serving throughput: every thread EXECUTEs a prepared
    ``SELECT ... WHERE pk = ?`` with rotating parameters — the shape the
    value-agnostic prepared-plan cache exists for."""
    db.execute("CREATE TABLE qps_p (id BIGINT PRIMARY KEY, v BIGINT)")
    db.execute("INSERT INTO qps_p VALUES " + ",".join(f"({i},{i * 3})" for i in range(1000)))

    def setup(s, i):
        s.prepare("SELECT v FROM qps_p WHERE id = ?", name="pt")
        s.execute_prepared("pt", [i])  # warm the per-session caches

    def worker(s, i, k):
        rows = s.execute_prepared("pt", [(i * 131 + k) % 1000]).rows
        if len(rows) != 1:  # never inside an assert: python -O strips it
            raise RuntimeError(f"point select returned {len(rows)} rows")

    return concurrent_qps(db, worker, QPS_THREADS, QPS_ITERS, setup=setup)


def qps_point_select_cold(db) -> float:
    """Cold-session point selects: a FRESH session per query over text SQL —
    the short-lived-connection serving shape. The instance-level AST cache
    and the cross-session point-get batcher are what keep this within reach
    of the warm-session number."""
    db.execute("CREATE TABLE qps_c (id BIGINT PRIMARY KEY, v BIGINT)")
    db.execute("INSERT INTO qps_c VALUES " + ",".join(f"({i},{i * 3})" for i in range(1000)))
    db.query("SELECT v FROM qps_c WHERE id = 0")

    def worker(_s, i, k):
        s2 = db.session()
        rows = s2.query(f"SELECT v FROM qps_c WHERE id = {(i * 7 + k) % 16}")
        if len(rows) != 1:  # never inside an assert: python -O strips it
            raise RuntimeError(f"cold point select returned {len(rows)} rows")

    return concurrent_qps(db, worker, QPS_THREADS, QPS_ITERS)


def qps_q1_concurrent(db) -> float:
    """Q1 under concurrency: N sessions hammer the same warm aggregation —
    measures how much of the fixed SQL-layer tax survives parallel load
    (device work serializes on the chip; the SQL layer must not add to it)."""
    def setup(s, i):
        s.execute("SET tidb_isolation_read_engines = 'tpu'")
        s.query(Q1)  # warm plan + device caches per session

    def worker(s, i, k):
        s.query(Q1)

    return concurrent_qps(db, worker, min(QPS_THREADS, 4), 3, setup=setup)


def chip_time(db, session, sql) -> float:
    """Amortized ON-CHIP time for one query's device task: dispatch the
    production-shaped kernel K times asynchronously and sync once, dividing
    out the host↔device round trip (the remote tunnel adds a fixed
    ~5-15ms/dispatch plus 60-800ms per synchronous fetch that says nothing
    about the chip; K=32 pushes the amortized dispatch share under ~3ms).
    Returns seconds per full-table run."""
    from tidb_tpu.copr import tpu_engine as te

    captured = {}
    real = te._execute_dag_device

    def cap(store, dag, region, ranges, read_ts, warn=None):
        captured["args"] = (dag, region, ranges, read_ts)
        return real(store, dag, region, ranges, read_ts, warn)

    te._execute_dag_device = cap
    try:
        session.query(sql)
    finally:
        te._execute_dag_device = real
    dag, region, ranges, read_ts = captured["args"]
    run_once, sync = te.device_probe_fn(db.store, dag, region, ranges, read_ts)
    sync(run_once())  # warm
    K = 32
    t0 = time.perf_counter()
    outs = [run_once() for _ in range(K)]
    sync(outs[-1])
    return (time.perf_counter() - t0) / K


_REMOTE_SERVER_SCRIPT = r"""
import sys, time
sys.path.insert(0, {repo!r})
import os
os.environ["BENCH_ROWS"] = str({rows})
os.environ["BENCH_JOIN_ROWS"] = str({jrows})
import bench
db, _ = bench.setup()
from tidb_tpu.kv.remote import StoreServer
srv = StoreServer(db.store)
print(f"PORT {{srv.start()}}", flush=True)
while True:
    time.sleep(1)
"""


def remote_probe():
    """Q1/Q3 through the REAL topology: this process is a pure SQL layer
    over a storage-server subprocess that owns the data AND the device (ref:
    tests/realtikvtest — the reference benches against real TiKV, not only
    unistore). Runs BEFORE the embedded benches so the parent process has
    not initialized the device backend the server needs to own."""
    import subprocess
    import threading

    repo = os.path.dirname(os.path.abspath(__file__))
    proc = subprocess.Popen(
        [sys.executable, "-c", _REMOTE_SERVER_SCRIPT.format(
            repo=repo, rows=N_ROWS, jrows=N_JOIN)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    got: list = []

    def reader():
        for line in proc.stdout:
            if line.startswith("PORT "):
                got.append(int(line.split()[1]))
                return

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    # drain stderr concurrently: a chatty child must not deadlock on a full
    # pipe buffer before it prints PORT
    err_chunks: list = []
    te = threading.Thread(
        target=lambda: err_chunks.append(proc.stderr.read()), daemon=True
    )
    te.start()
    t.join(timeout=600)
    if not got:
        proc.kill()
        err_tail = (err_chunks[0] if err_chunks else "" or "")[-2000:]
        raise RuntimeError(f"bench store server did not come up: {err_tail}")
    try:
        import tidb_tpu

        db = tidb_tpu.open(remote=f"127.0.0.1:{got[0]}")
        s = db.session()
        s.execute("SET tidb_isolation_read_engines = 'tpu'")
        q1_remote = timed(s, Q1, max(1, REPS // 2))
        s.execute("ANALYZE TABLE orders")
        s.execute("ANALYZE TABLE lineitem2")
        q3_remote = timed(s, Q3, max(1, REPS // 2))
        return q1_remote, q3_remote
    finally:
        proc.kill()
        try:
            proc.wait(timeout=30)
        except Exception:
            pass  # a slow reap must not discard the measured results


def main():
    try:
        q1_remote, q3_remote = remote_probe()
    except Exception as e:  # the remote topology must never sink the bench
        print(f"remote probe failed: {e!r}", file=sys.stderr)
        q1_remote = q3_remote = None
    db, load_s = setup()
    s = db.session()

    s.execute("SET tidb_isolation_read_engines = 'tpu'")
    q1_tpu = timed(s, Q1, REPS)

    def chip(sql, label):
        try:
            return chip_time(db, s, sql)
        except Exception as e:  # best-effort diagnostics — but never silently
            print(f"{label} chip probe failed: {e!r}", file=sys.stderr)
            return None

    q1_chip = chip(Q1, "q1")
    q6_chip = chip(Q6, "q6")
    q10_chip = chip(Q10, "q10")
    q6_tpu = timed(s, Q6, REPS)
    cnt_tpu = timed(s, COUNT_STAR, REPS)
    q10_tpu = timed(s, Q10, REPS)
    # the Expand fusion vs the per-set union (same query, toggled rewrite)
    rollup_fused = timed(s, Q1_ROLLUP, max(1, REPS // 2))
    s.execute("SET tidb_opt_fused_rollup = 0")
    rollup_union = timed(s, Q1_ROLLUP, max(1, REPS // 2))
    s.execute("SET tidb_opt_fused_rollup = 1")
    q3_tpu = timed(s, Q3, max(1, REPS // 2))
    win_tpu = timed(s, WINDOWED, max(1, REPS // 2))
    tpu_rows = s.query(Q1)

    # concurrent-QPS lanes (threads × sessions over this same DB); failures
    # are diagnostic, never sink the headline metric
    def qps(fn, label):
        try:
            return fn(db)
        except Exception as e:
            print(f"{label} qps lane failed: {e!r}", file=sys.stderr)
            return None

    qps_ps = qps(qps_point_select, "point_select")
    qps_cold = qps(qps_point_select_cold, "point_select_cold")
    qps_q1 = qps(qps_q1_concurrent, "q1_concurrent")

    s.execute("SET tidb_isolation_read_engines = 'host'")
    q1_host = timed(s, Q1, HOST_REPS)
    q6_host = timed(s, Q6, HOST_REPS)
    cnt_host = timed(s, COUNT_STAR, HOST_REPS)
    q10_host = timed(s, Q10, HOST_REPS)
    s.execute("SET tidb_allow_mpp = 0")  # host reference path for the join
    q3_host = timed(s, Q3, HOST_REPS)
    win_host = timed(s, WINDOWED, HOST_REPS)
    s.execute("SET tidb_allow_mpp = 1")
    host_rows = s.query(Q1)

    assert [r[:2] + tuple(str(x) for x in r[2:]) for r in tpu_rows] == [
        r[:2] + tuple(str(x) for x in r[2:]) for r in host_rows
    ], "engine results diverge"

    value = N_ROWS / q1_tpu
    vs = q1_host / q1_tpu
    result = {
        "metric": "tpch_q1_sf~1_rows_per_sec_per_chip",
        "value": round(value),
        "unit": "rows/s",
        "vs_baseline": round(vs, 2),
        "detail": {
            "rows": N_ROWS,
            "q1_tpu_ms": round(q1_tpu * 1e3, 1),
            # amortized device-only time (tunnel RTT divided out): what the
            # chip itself sustains on Q1
            "q1_chip_ms": round(q1_chip * 1e3, 1) if q1_chip else None,
            "q1_chip_rows_per_sec": round(N_ROWS / q1_chip) if q1_chip else None,
            "q1_host_ms": round(q1_host * 1e3, 1),
            "q6_tpu_ms": round(q6_tpu * 1e3, 1),
            "q6_chip_ms": round(q6_chip * 1e3, 1) if q6_chip else None,
            "q10_chip_ms": round(q10_chip * 1e3, 1) if q10_chip else None,
            "q6_host_ms": round(q6_host * 1e3, 1),
            "q6_speedup": round(q6_host / q6_tpu, 2),
            "count_tpu_ms": round(cnt_tpu * 1e3, 1),
            # the fixed SQL-layer tax: COUNT(*) is near-zero device compute,
            # so its warm end-to-end latency IS the per-query overhead the
            # fast lane attacks (parse/plan reuse, shared pool, digest memo)
            "fixed_overhead_ms": round(cnt_tpu * 1e3, 1),
            "qps_point_select": round(qps_ps, 1) if qps_ps else None,
            "qps_point_select_cold": round(qps_cold, 1) if qps_cold else None,
            "qps_q1_concurrent": round(qps_q1, 2) if qps_q1 else None,
            "count_host_ms": round(cnt_host * 1e3, 1),
            "q10_topn_tpu_ms": round(q10_tpu * 1e3, 1),
            "rollup_fused_ms": round(rollup_fused * 1e3, 1),
            "rollup_union_ms": round(rollup_union * 1e3, 1),
            "q10_topn_host_ms": round(q10_host * 1e3, 1),
            "q3_join_mpp_ms": round(q3_tpu * 1e3, 1),
            "q3_join_host_ms": round(q3_host * 1e3, 1),
            # the REAL topology: SQL layer + storage-server process over TCP
            "q1_remote_ms": round(q1_remote * 1e3, 1) if q1_remote else None,
            "q3_remote_mpp_ms": round(q3_remote * 1e3, 1) if q3_remote else None,
            "window_tpu_ms": round(win_tpu * 1e3, 1),
            "window_host_ms": round(win_host * 1e3, 1),
            "load_s": round(load_s, 1),
            "platform": _platform(),
        },
    }
    print(json.dumps(result))


def _platform():
    try:
        import jax

        return str(jax.devices()[0].platform)
    except Exception as e:  # pragma: no cover
        return f"unknown ({e})"


if __name__ == "__main__":
    main()
